"""The soak orchestrator: a chaos-driven fleet behind one driver loop.

Boots a production-shaped fleet — a LOCAL instance (UDP statsd ingest,
checkpointed, forwarding) → an HTTP PROXY (consistent-hash fan-out,
peers-file discovery) → a GLOBAL aggregator (checkpointed, handoff
plane armed, Datadog streamed egress + an exact-accounting channel
sink) — then drives the scenario's intervals: mixed traffic in, driven
flushes through, the seeded chaos schedule on top (role kills, sink
black-hole/5xx/latency windows, injected disk-full and
flush-deadline-pressure faults), a steady-state sample per interval,
and the full gate vector at the end (``soak.gates``).

Two interchangeable fleet backends share the driver:

* :class:`InProcessFleet` — all three roles in this process; kills are
  ``Server.crash_stop()`` (the SIGKILL twin: no final flush, no
  checkpoint truncation, no handoff quiesce). Fast enough for the
  tier-1 smoke test.
* :class:`ProcessFleet` — each role is a real child process
  (``python -m veneur_tpu.soak.child``) on fixed ports; kills are real
  ``SIGKILL``. The bench ``14_soak`` lane runs this one.

Conservation across a kill is exact because a kill is scheduled
BETWEEN intervals: the driver settles ingest, commits a checkpoint
(retried through injected ENOSPC until the disk admits it), folds the
dying generation's monotone counters into the run ledger (parked sink
rows become counted ``dd_crash_lost``), and only then kills. The
restarted process restores from the checkpoint epoch and the ledger
closes end to end. Mid-flush kill atomicity is separately covered by
``tests/test_persist_e2e.py`` / ``tests/test_handoff_e2e.py``.
"""

from __future__ import annotations

import logging
import random
import socket
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from veneur_tpu.soak.gates import (GateResult, SoakLedger, enforce,
                                   gate_vector, run_gates)
from veneur_tpu.soak.monitor import (IntervalSample, SteadyStateMonitor,
                                     read_rss_kb)
from veneur_tpu.soak.scenario import (KIND_KILL_FOREVER, MODE_BLACKHOLE,
                                      MODE_HTTP_5XX, MODE_OK, MODE_SLOW,
                                      ROLE_GLOBAL, ROLE_LOCAL, ROLE_PROXY,
                                      ROLE_STANDBY, SoakScenario)

log = logging.getLogger("veneur.soak")

GLOBAL_PREFIX = "soak.c"   # counters tagged veneurglobalonly (the ledger)
LOCAL_PREFIX = "soak.l"    # counters aggregated at the local instance


class ChaosPost:
    """The global's Datadog POST transport under scenario control:
    ``ok`` → 202, ``http_5xx`` → 503, ``blackhole`` → raises (the
    refused-connection twin), ``slow`` → latency then 202. One
    instance survives sink generations so an outage window spans a
    global restart."""

    def __init__(self, slow_s: float = 0.05):
        self.mode = MODE_OK
        self.slow_s = slow_s
        self.posts = 0
        self.failures = 0

    def __call__(self, url, body, **kwargs) -> int:
        self.posts += 1
        if self.mode == MODE_BLACKHOLE:
            self.failures += 1
            raise OSError("soak: injected sink black hole")
        if self.mode == MODE_HTTP_5XX:
            self.failures += 1
            return 503
        if self.mode == MODE_SLOW:
            time.sleep(self.slow_s)
        return 202


def pick_port(kind: int = socket.SOCK_DGRAM) -> int:
    """A fixed port the fleet keeps across restarts (bind-0, read,
    close). TCP listeners here use SO_REUSEPORT (OpsServer does too)
    so the address survives kill/rebind cycles."""
    s = socket.socket(socket.AF_INET, kind)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class FleetSpec:
    """Everything needed to (re)build any role — JSON-serializable so
    the subprocess children build byte-identical servers."""

    root: str                  # scratch dir: checkpoints, spool, peers
    udp_port: int
    proxy_port: int
    global_port: int
    fault_rate: float
    fault_kinds: str
    seed: int
    requeue_max_bytes: int
    breaker_reset_s: float = 0.75
    # HA (kill_forever scenarios): a warm-standby global on its own
    # port plus a file:// lease; lease_ttl_s == 0 means HA off
    standby_port: int = 0
    lease_ttl_s: float = 0.0

    def to_json(self) -> dict:
        return dict(self.__dict__)

    @classmethod
    def from_json(cls, d: dict) -> "FleetSpec":
        return cls(**d)

    @classmethod
    def for_scenario(cls, scenario: SoakScenario, root: str) -> "FleetSpec":
        ha = scenario.kind == KIND_KILL_FOREVER
        return cls(root=root, udp_port=pick_port(),
                   proxy_port=pick_port(socket.SOCK_STREAM),
                   global_port=pick_port(socket.SOCK_STREAM),
                   fault_rate=scenario.fault_rate,
                   fault_kinds=scenario.fault_kinds,
                   seed=scenario.seed,
                   requeue_max_bytes=scenario.thresholds.requeue_max_bytes,
                   standby_port=pick_port(socket.SOCK_STREAM) if ha else 0,
                   lease_ttl_s=1.5 if ha else 0.0)


# -- role construction (shared by InProcessFleet and soak.child) -----------

def build_local_server(spec: FleetSpec):
    """The local role: UDP statsd ingest on the fixed port, driven
    cadence, checkpointed, forwarding to the proxy."""
    from veneur_tpu.config import Config
    from veneur_tpu.server import Server
    from veneur_tpu.sinks import ChannelMetricSink

    cfg = Config(
        statsd_listen_addresses=[f"udp://127.0.0.1:{spec.udp_port}"],
        interval="86400s",  # driven cadence: the loop never self-fires
        forward_address=f"http://127.0.0.1:{spec.proxy_port}",
        aggregates=["count"], percentiles=[0.5], num_readers=2,
        store_initial_capacity=64, store_chunk=128,
        checkpoint_path=f"{spec.root}/local.ckpt",
        checkpoint_interval="3600s",
        fault_injection_rate=spec.fault_rate,
        fault_injection_seed=spec.seed + 1,
        fault_injection_kinds="disk_full,deadline_pressure")
    sink = ChannelMetricSink()
    server = Server(cfg, metric_sinks=[sink])
    server.start()
    return server, sink


def build_global_server(spec: FleetSpec, chaos_post: ChaosPost,
                        role: str = ROLE_GLOBAL):
    """The global role: /import ingest on the fixed ops port, handoff
    plane armed over the peers file, checkpointed, channel sink for
    exact value accounting + Datadog streamed egress through the
    scenario's :class:`ChaosPost`. ``role`` may be ``standby`` (HA
    scenarios): same shape on ``spec.standby_port``, contending for
    the shared file lease but replicating to nobody. Returns
    ``(server, channel_sink, dd_sink, offered_counter)`` where
    ``offered_counter`` is a one-slot list counting rows offered to
    the chunk path this generation."""
    from veneur_tpu.config import Config
    from veneur_tpu.resilience import CircuitBreaker, RetryPolicy
    from veneur_tpu.server import Server
    from veneur_tpu.sinks import ChannelMetricSink
    from veneur_tpu.sinks.datadog import DatadogMetricSink

    port = spec.standby_port if role == ROLE_STANDBY else spec.global_port
    self_addr = f"http://127.0.0.1:{port}"
    # each global life gets its OWN handoff ring (itself); the proxy's
    # routing is lease-driven in HA mode, peers.txt-driven otherwise
    peers = (f"{spec.root}/standby_peers.txt" if role == ROLE_STANDBY
             else f"{spec.root}/peers.txt")
    with open(peers, "w") as f:
        f.write(self_addr + "\n")
    ha_keys = {}
    if spec.lease_ttl_s > 0:
        ha_keys = dict(
            lease_path=f"file://{spec.root}/lease",
            lease_ttl=f"{spec.lease_ttl_s}s",
            lease_renew_interval=f"{spec.lease_ttl_s / 3.0:.3f}s")
        if role == ROLE_GLOBAL:
            # the active streams its retired flush epochs to the
            # standby; the standby replicates to nobody (its shadow is
            # the receiving end)
            ha_keys["standby_peers"] = \
                f"http://127.0.0.1:{spec.standby_port}"
    cfg = Config(
        statsd_listen_addresses=[], interval="86400s",
        http_address=f"127.0.0.1:{port}",
        aggregates=["count"], percentiles=[0.5],
        store_initial_capacity=64, store_chunk=128,
        checkpoint_path=f"{spec.root}/{role}.ckpt",
        checkpoint_interval="3600s",
        handoff_enabled=True,
        handoff_self=self_addr,
        handoff_peers=f"file://{peers}",
        fault_injection_rate=spec.fault_rate,
        fault_injection_seed=spec.seed + (2 if role == ROLE_GLOBAL
                                          else 4),
        fault_injection_kinds=spec.fault_kinds,
        sink_requeue_max_bytes=spec.requeue_max_bytes,
        **ha_keys)
    channel = ChannelMetricSink()
    dd = DatadogMetricSink(
        interval=10.0, flush_max_per_body=100, hostname="soak-global",
        tags=["soak:1"], dd_hostname="http://dd.soak.invalid",
        api_key="soak", post=chaos_post,
        retry_policy=RetryPolicy(max_attempts=1),
        breaker=CircuitBreaker(failure_threshold=3,
                               reset_timeout=spec.breaker_reset_s,
                               name="datadog"),
        requeue_max_bytes=spec.requeue_max_bytes)
    offered = [0]
    orig_chunk = dd.flush_chunk

    def counting_flush_chunk(chunk):
        offered[0] += chunk.rows
        orig_chunk(chunk)

    dd.flush_chunk = counting_flush_chunk
    server = Server(cfg, metric_sinks=[channel, dd])
    server.start()
    return server, channel, dd, offered


def build_proxy(spec: FleetSpec):
    """The proxy role: HTTP /import fan-out over the peers-file ring —
    or, in HA mode, over the lease (:class:`LeaderDiscoverer`: the
    holder IS the membership, so a takeover re-routes the fan-out
    within one ordinary refresh, no new routing machinery)."""
    from veneur_tpu.config import ProxyConfig
    from veneur_tpu.proxy.proxy import Proxy

    if spec.lease_ttl_s > 0:
        from veneur_tpu.discovery import (LeaderDiscoverer,
                                          lease_backend_from_url)

        disc = LeaderDiscoverer(
            lease_backend_from_url(f"file://{spec.root}/lease"))
        # chase a lease transition quickly: the refresh cadence bounds
        # detect→re-route, and the active already holds at proxy boot
        cfg = ProxyConfig(http_address=f"127.0.0.1:{spec.proxy_port}",
                          forward_timeout="5s",
                          consul_refresh_interval="250ms")
    else:
        from veneur_tpu.discovery import FilePeersDiscoverer

        peers = f"{spec.root}/peers.txt"
        with open(peers, "w") as f:
            f.write(f"http://127.0.0.1:{spec.global_port}\n")
        disc = FilePeersDiscoverer(peers)
        cfg = ProxyConfig(http_address=f"127.0.0.1:{spec.proxy_port}",
                          forward_timeout="5s")
    proxy = Proxy(cfg, discoverer=disc)
    proxy.start()
    return proxy


def drain_channel(sink, prefix: str) -> float:
    """Drain every queued flush batch; return the summed value of
    metrics whose name starts with ``prefix`` (counters flush raw
    counts, so the sum is the exact ingested value)."""
    import queue

    total = 0.0
    while True:
        try:
            batch = sink.queue.get_nowait()
        except queue.Empty:
            return total
        for m in batch:
            if m.name.startswith(prefix):
                total += m.value


def global_sample_fields(server, dd, pid: int = 0) -> dict:
    """One interval's steady-state reading of a global server (shared
    by the in-process fleet and the subprocess child)."""
    from veneur_tpu.obs import kernels

    entry = {}
    if server.obs_timeline is not None:
        entries = server.obs_timeline.entries(1)
        entry = entries[-1] if entries else {}
    ckpt = server.checkpointer
    mgr = server.handoff_manager
    return {
        "rss_kb": read_rss_kb(pid),
        "compiles": kernels.compiles_total(),
        "coverage_ratio": entry.get("coverage_ratio"),
        "e2e_age_ns": entry.get("e2e_age_ns"),
        "overload_level": server.overload.level_nowait(),
        "breaker_gauge": (dd.breaker.state_gauge()
                          if dd.breaker is not None else 0.0),
        "requeue_bytes": dd.chunk_requeue_bytes(),
        "rows_pending": dd.chunk_rows_pending(),
        "ckpt_write_errors": ckpt.write_errors if ckpt else 0,
        "spool_errors": mgr.spool_errors_total if mgr else 0,
        "degradations": tuple(server.degradation()),
    }


def global_counters(server, dd, offered) -> Dict[str, int]:
    """The global generation's monotone counters, read just before a
    kill (folded with parked rows → crash_lost) and once at the end."""
    mgr = server.handoff_manager
    return {
        "dd_offered": offered[0],
        "dd_acked": dd.chunk_rows_acked,
        "dd_dropped": dd.chunk_rows_dropped,
        "dd_pending": dd.chunk_rows_pending(),
        "shed": server.overload.shed_total(),
        "quarantined": server.quarantine.total(),
        "ckpt_write_errors": (server.checkpointer.write_errors
                              if server.checkpointer else 0),
        "spool_errors": mgr.spool_errors_total if mgr else 0,
    }


def local_counters(server) -> Dict[str, int]:
    return {
        "shed": server.overload.shed_total(),
        "quarantined": server.quarantine.total(),
        "ckpt_write_errors": (server.checkpointer.write_errors
                              if server.checkpointer else 0),
    }


def checkpoint_with_retry(server, attempts: int = 400,
                          pause_s: float = 0.005) -> int:
    """Commit a checkpoint, riding through injected/real ENOSPC (the
    write path never raises; it counts and returns False). Returns the
    attempt count; raises only if the disk never admits the write."""
    ckpt = server.checkpointer
    if ckpt is None:
        return 0
    for i in range(attempts):
        if ckpt.write_once():
            return i + 1
        time.sleep(pause_s)
    raise RuntimeError(
        f"checkpoint to {ckpt.path} failed {attempts} times "
        f"(last error: {ckpt.last_error})")


# -- the in-process fleet ---------------------------------------------------

class InProcessFleet:
    """All three roles in this process (plus the warm standby in HA
    scenarios). Kills use ``Server.crash_stop()`` — the in-process
    SIGKILL twin (no final flush, no checkpoint, no lease release)."""

    def __init__(self, scenario: SoakScenario, root: str):
        self.spec = FleetSpec.for_scenario(scenario, root)
        self.chaos = ChaosPost()
        self._sender: Optional[socket.socket] = None
        self.local = self.local_sink = None
        self.glob = self.g_channel = self.g_dd = None
        self._g_offered = [0]
        self.proxy = None
        self.sby = self.s_channel = self.s_dd = None
        self._s_offered = [0]

    def start(self) -> None:
        self.glob, self.g_channel, self.g_dd, self._g_offered = \
            build_global_server(self.spec, self.chaos)
        if self.spec.lease_ttl_s > 0:
            # the active must hold the lease before the standby's
            # elector (or the proxy's first refresh) can observe it —
            # boot order is the determinism of who is active
            self._wait_leader()
            self.s_chaos = ChaosPost()
            self.sby, self.s_channel, self.s_dd, self._s_offered = \
                build_global_server(self.spec, self.s_chaos,
                                    role=ROLE_STANDBY)
        self.proxy = build_proxy(self.spec)
        self.local, self.local_sink = build_local_server(self.spec)
        self._sender = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sender.connect(("127.0.0.1", self.spec.udp_port))

    def _wait_leader(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            sm = getattr(self.glob, "standby_manager", None)
            if sm is not None and sm.is_leader:
                return
            time.sleep(0.02)
        raise RuntimeError("active global never acquired the boot lease")

    def stop(self) -> None:
        for closer in (
                lambda: self._sender and self._sender.close(),
                lambda: self.local and self.local.shutdown(),
                lambda: self.proxy and self.proxy.shutdown(),
                lambda: self.sby and self.sby.shutdown(),
                lambda: self.glob and self.glob.shutdown()):
            try:
                closer()
            except Exception:
                log.exception("soak fleet stop")

    def send(self, lines: List[bytes]) -> None:
        for line in lines:
            self._sender.send(line)

    def local_processed(self) -> int:
        return self.local.store.processed

    def global_imported(self) -> int:
        return self.glob.store.imported

    def set_sink_mode(self, mode: str) -> None:
        self.chaos.mode = mode

    def flush_local(self) -> float:
        self.local.flush()
        return drain_channel(self.local_sink, LOCAL_PREFIX)

    def flush_global(self) -> Tuple[float, dict]:
        self.glob.flush()
        emitted = drain_channel(self.g_channel, GLOBAL_PREFIX)
        return emitted, global_sample_fields(self.glob, self.g_dd)

    def checkpoint(self, role: str) -> int:
        if role == ROLE_LOCAL:
            return checkpoint_with_retry(self.local)
        if role == ROLE_GLOBAL:
            return checkpoint_with_retry(self.glob)
        return 0

    def counters(self, role: str) -> Dict[str, int]:
        if role == ROLE_GLOBAL:
            return global_counters(self.glob, self.g_dd, self._g_offered)
        if role == ROLE_LOCAL:
            return local_counters(self.local)
        return {}

    def kill_restart(self, role: str) -> None:
        if role == ROLE_LOCAL:
            self.local.crash_stop()
            self.local, self.local_sink = build_local_server(self.spec)
        elif role == ROLE_GLOBAL:
            self.glob.crash_stop()
            self.glob, self.g_channel, self.g_dd, self._g_offered = \
                build_global_server(self.spec, self.chaos)
        elif role == ROLE_PROXY:
            # the proxy is stateless; its crash twin is an immediate
            # teardown + rebind on the same fixed port
            try:
                self.proxy.shutdown()
            except Exception:
                pass
            self.proxy = build_proxy(self.spec)

    # -- HA takeover (kill_forever scenarios) --------------------------------

    def ha_status(self) -> dict:
        server = self.sby if self.sby is not None else self.glob
        sm = getattr(server, "standby_manager", None)
        return sm.snapshot() if sm is not None else {}

    def kill_forever(self) -> None:
        """SIGKILL-twin the active with NO restart: the standby becomes
        the fleet's global (its lease poll promotes it; the driver's
        view swaps immediately so flush/counters target the survivor)."""
        self.glob.crash_stop()
        self.glob, self.g_channel, self.g_dd, self._g_offered = \
            (self.sby, self.s_channel, self.s_dd, self._s_offered)
        self.chaos = self.s_chaos
        self.sby = self.s_channel = self.s_dd = None

    def await_reroute(self, timeout_s: float = 15.0) -> bool:
        """Wait for the proxy ring to chase the lease onto the promoted
        standby (one ordinary membership refresh)."""
        want = [f"http://127.0.0.1:{self.spec.standby_port}"]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if list(self.proxy.ring.members()) == want:
                return True
            time.sleep(0.05)
        return False


# -- the multi-process fleet ------------------------------------------------

class _Child:
    """One role as a real child process speaking the line protocol of
    ``veneur_tpu.soak.child`` (commands on stdin, one JSON ack per
    command on stdout, logs on stderr)."""

    def __init__(self, role: str, spec: FleetSpec):
        self.role = role
        self.spec = spec
        self.proc = None
        self.ready: dict = {}

    def spawn(self) -> None:
        import json
        import subprocess
        import sys

        spec_path = f"{self.spec.root}/{self.role}.spec.json"
        with open(spec_path, "w") as f:
            json.dump(self.spec.to_json(), f)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "veneur_tpu.soak.child",
             self.role, spec_path],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1)
        self.ready = self._read_line(timeout_s=120.0)
        if not self.ready.get("ready"):
            raise RuntimeError(f"soak {self.role} child failed to boot: "
                               f"{self.ready}")

    def _read_line(self, timeout_s: float = 60.0) -> dict:
        import json
        import select

        deadline = time.monotonic() + timeout_s
        buf = ""
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise RuntimeError(
                    f"soak {self.role} child unresponsive "
                    f"(rc={self.proc.poll()})")
            r, _, _ = select.select([self.proc.stdout], [], [], left)
            if not r:
                continue
            buf = self.proc.stdout.readline()
            if buf == "":
                raise RuntimeError(
                    f"soak {self.role} child died "
                    f"(rc={self.proc.poll()})")
            buf = buf.strip()
            if buf:
                return json.loads(buf)

    def command(self, cmd: str, timeout_s: float = 60.0) -> dict:
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()
        return self._read_line(timeout_s)

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=30)

    def quit(self) -> None:
        try:
            self.command("quit", timeout_s=30.0)
        except Exception:
            pass
        try:
            self.proc.wait(timeout=30)
        except Exception:
            self.proc.kill()


class ProcessFleet:
    """Each role a real OS process on fixed ports; kills are real
    SIGKILL. The driver's view is identical to the in-process fleet —
    the children self-report their samples and counters."""

    def __init__(self, scenario: SoakScenario, root: str):
        self.spec = FleetSpec.for_scenario(scenario, root)
        self.children: Dict[str, _Child] = {}
        self._sender: Optional[socket.socket] = None
        self._mode = MODE_OK

    def start(self) -> None:
        ha = self.spec.lease_ttl_s > 0
        roles = ((ROLE_GLOBAL, ROLE_STANDBY, ROLE_PROXY, ROLE_LOCAL)
                 if ha else (ROLE_GLOBAL, ROLE_PROXY, ROLE_LOCAL))
        for role in roles:
            child = _Child(role, self.spec)
            child.spawn()
            self.children[role] = child
            if ha and role == ROLE_GLOBAL:
                # boot order is the determinism of who is active: the
                # first global must hold the lease before the standby
                # (or the proxy's fatal-on-empty first refresh) looks
                self._wait_leader(child)
        self._sender = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sender.connect(("127.0.0.1", self.spec.udp_port))

    @staticmethod
    def _wait_leader(child: "_Child", timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if child.command("hastatus").get("ha", {}).get("is_leader"):
                return
            time.sleep(0.05)
        raise RuntimeError("active global never acquired the boot lease")

    def stop(self) -> None:
        if self._sender is not None:
            self._sender.close()
        for child in self.children.values():
            child.quit()

    def send(self, lines: List[bytes]) -> None:
        for line in lines:
            self._sender.send(line)

    def local_processed(self) -> int:
        return self.children[ROLE_LOCAL].command("processed")["v"]

    def global_imported(self) -> int:
        return self.children[ROLE_GLOBAL].command("imported")["v"]

    def set_sink_mode(self, mode: str) -> None:
        self._mode = mode
        self.children[ROLE_GLOBAL].command(f"mode {mode}")

    def flush_local(self) -> float:
        return self.children[ROLE_LOCAL].command(
            "flush", timeout_s=120.0)["emitted"]

    def flush_global(self) -> Tuple[float, dict]:
        ack = self.children[ROLE_GLOBAL].command("flush", timeout_s=120.0)
        sample = ack["sample"]
        sample["degradations"] = tuple(sample.get("degradations", ()))
        return ack["emitted"], sample

    def checkpoint(self, role: str) -> int:
        if role == ROLE_PROXY:
            return 0
        ack = self.children[role].command("ckpt", timeout_s=120.0)
        if not ack.get("ok"):
            raise RuntimeError(f"soak {role} child checkpoint failed: {ack}")
        return ack.get("attempts", 1)

    def counters(self, role: str) -> Dict[str, int]:
        if role == ROLE_PROXY:
            return {}
        return self.children[role].command("counters")["counters"]

    def kill_restart(self, role: str) -> None:
        self.children[role].kill()
        child = _Child(role, self.spec)
        child.spawn()
        self.children[role] = child
        if role == ROLE_GLOBAL and self._mode != MODE_OK:
            # the outage window outlives the process it was imposed on
            child.command(f"mode {self._mode}")

    # -- HA takeover (kill_forever scenarios) --------------------------------

    def ha_status(self) -> dict:
        child = self.children.get(ROLE_STANDBY) \
            or self.children[ROLE_GLOBAL]
        return child.command("hastatus").get("ha", {})

    def kill_forever(self) -> None:
        """Real SIGKILL of the active global, NO respawn: the standby
        child becomes the fleet's global for every later command."""
        self.children[ROLE_GLOBAL].kill()
        self.children[ROLE_GLOBAL] = self.children.pop(ROLE_STANDBY)

    def await_reroute(self, timeout_s: float = 15.0) -> bool:
        want = [f"http://127.0.0.1:{self.spec.standby_port}"]
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                members = self.children[ROLE_PROXY].command(
                    "ring").get("members")
            except Exception:
                members = None
            if members == want:
                return True
            time.sleep(0.1)
        return False


# -- the driver -------------------------------------------------------------

@dataclass
class SoakReport:
    scenario: SoakScenario
    ledger: SoakLedger
    monitor: SteadyStateMonitor
    results: List[GateResult] = field(default_factory=list)
    # per-interval conservation timeline from the armed LedgerAudit
    # (lint/ledger_audit.py) — settled only at terminal settlement
    ledger_timeline: List[dict] = field(default_factory=list)
    # per-interval live-device-buffer timeline from the armed
    # BufferCensus (lint/buffer_census.py) — the donation-safety
    # pass's runtime twin, judged at terminal settlement
    buffer_timeline: List[dict] = field(default_factory=list)

    def vector(self) -> dict:
        return gate_vector(self.results)


def interval_traffic(scenario: SoakScenario,
                     idx: int) -> Tuple[List[bytes], int, int, int]:
    """The interval's deterministic production-shaped mix: global-only
    counters (the exact ledger), global-only timers (digest
    forwarding), local counters and a gauge. Returns
    ``(datagrams, global_counter_value, local_counter_value,
    distinct_global_series)``."""
    rng = random.Random(scenario.seed * 1000003 + idx)
    lines: List[bytes] = []
    names = set()
    sent_c = 0
    for i in range(scenario.counters_per_interval):
        name = f"soak.c{i % 8}"
        v = rng.randint(1, 5)
        lines.append(f"{name}:{v}|c|#veneurglobalonly".encode())
        names.add(name)
        sent_c += v
    for i in range(scenario.timers_per_interval):
        name = f"soak.t{i % 4}"
        lines.append(f"{name}:{rng.uniform(0.5, 20.0):.3f}|ms"
                     f"|#veneurglobalonly".encode())
        names.add(name)
    sent_l = 0
    for i in range(8):
        lines.append(f"soak.l{i % 4}:1|c".encode())
        sent_l += 1
    lines.append(b"soak.g:42|g")
    rng.shuffle(lines)
    return lines, sent_c, sent_l, len(names)


def _settle(read: Callable[[], int], target: int, timeout_s: float = 15.0,
            stable_s: float = 0.15) -> int:
    """Poll until ``read()`` reaches ``target`` AND holds still for
    ``stable_s`` (self-telemetry re-enters the stores asynchronously,
    so >= alone can fire early)."""
    deadline = time.monotonic() + timeout_s
    last, last_change = read(), time.monotonic()
    while time.monotonic() < deadline:
        cur = read()
        if cur != last:
            last, last_change = cur, time.monotonic()
        elif cur >= target and time.monotonic() - last_change >= stable_s:
            return cur
        time.sleep(0.01)
    return last


def _fold(ledger: SoakLedger, counters: Dict[str, int],
          crash: bool) -> None:
    ledger.shed += counters.get("shed", 0)
    ledger.quarantined += counters.get("quarantined", 0)
    ledger.ckpt_write_errors += counters.get("ckpt_write_errors", 0)
    ledger.spool_errors += counters.get("spool_errors", 0)
    ledger.dd_offered += counters.get("dd_offered", 0)
    ledger.dd_acked += counters.get("dd_acked", 0)
    ledger.dd_dropped += counters.get("dd_dropped", 0)
    pending = counters.get("dd_pending", 0)
    if crash:
        ledger.dd_crash_lost += pending
    else:
        ledger.dd_pending += pending


def _takeover(scenario: SoakScenario, fleet, ledger: SoakLedger,
              idx: int, sent_c: int,
              say: Callable[[str], None]) -> Tuple[float, dict]:
    """The kill_forever pivot. The interval's traffic is settled into
    the active but deliberately NOT flushed — that un-flushed tail is
    the bounded, accounted loss. Wait until replication is current
    (every PRIOR interval's flush reached the standby), measure the
    exact loss from the settled ledger, SIGKILL the active with no
    restart, time the standby's lease takeover, wait for the proxy to
    re-route, and take the first good flush from the survivor."""
    thr = scenario.thresholds
    # replication currency: the active flushed (and so replicated)
    # once per prior interval; insist the standby has received them
    # all, so the loss stays bounded by THIS interval's tail
    deadline = time.monotonic() + 10.0
    while (fleet.ha_status().get("receives_total", 0) < idx
           and time.monotonic() < deadline):
        time.sleep(0.05)
    # fold the active's monotone counters now — it dies next, and its
    # parked sink rows die with it (crash fold)
    _fold(ledger, fleet.counters(ROLE_GLOBAL), crash=True)
    # PEEK the local's shed/quarantine tallies without folding (the
    # end-of-run fold still owns them): accounted_lost must exclude
    # value the pipeline already accounted upstream of the active
    lc = fleet.counters(ROLE_LOCAL)
    ledger.accounted_lost = int(round(
        ledger.sent_global - ledger.emitted_global - ledger.shed
        - ledger.quarantined - lc.get("shed", 0)
        - lc.get("quarantined", 0)))
    ledger.takeover_loss_bound = sent_c
    t_kill = time.monotonic()
    fleet.kill_forever()
    say(f"interval {idx}: SIGKILL active global, no restart "
        f"(un-flushed tail value {ledger.accounted_lost})")
    deadline = time.monotonic() + thr.takeover_detect_max_s + 5.0
    st = fleet.ha_status()
    while not st.get("is_leader") and time.monotonic() < deadline:
        time.sleep(0.05)
        st = fleet.ha_status()
    if st.get("is_leader"):
        ledger.takeover_detect_s = time.monotonic() - t_kill
    ledger.promotions = 1 if st.get("promoted") else 0
    fleet.await_reroute()
    emitted, sample = fleet.flush_global()
    ledger.takeover_first_flush_s = time.monotonic() - t_kill
    say(f"interval {idx}: standby promoted in "
        f"{ledger.takeover_detect_s:.2f}s, first flush at "
        f"+{ledger.takeover_first_flush_s:.2f}s")
    return emitted, sample


def run_soak(scenario: SoakScenario, fleet,
             enforce_gates: bool = True,
             progress: Optional[Callable[[str], None]] = None
             ) -> SoakReport:
    """Drive the scenario over ``fleet`` (InProcessFleet or
    ProcessFleet): per interval — scheduled kills (checkpoint → fold →
    kill → restart), the sink outage mode, deterministic traffic,
    settled driven flushes local→global, one steady-state sample. Then
    terminal settlement (flush rounds until the pipeline drains), the
    end-of-run counter fold, and the gate vector. Raises
    :class:`~veneur_tpu.soak.gates.SoakGateError` on any violated gate
    unless ``enforce_gates=False``."""
    say = progress or (lambda s: log.info("%s", s))
    monitor = SteadyStateMonitor(scenario.thresholds.warmup_intervals)
    ledger = SoakLedger()
    # the drop-flow pass's runtime twin rides every soak run: per-
    # interval timeline snapshots (un-asserted — requeued state is
    # legitimately in flight mid-chaos), one SETTLED check after
    # terminal settlement where the cumulative identity is exact
    from veneur_tpu.lint.buffer_census import BufferCensus
    from veneur_tpu.lint.ledger_audit import for_soak_ledger

    audit = for_soak_ledger(ledger)
    # the donation-safety pass's runtime twin rides next to it: the
    # live-device-buffer census arms once warmup allocation (store
    # planes, first-flush compiles) is done, samples every interval,
    # and judges settled zero-growth as the device_buffers_bounded
    # gate. With a ProcessFleet the driver owns no device arrays, so
    # the census reads zero and the gate passes vacuously — the
    # in-process soak and the buffer_census fixture carry the teeth.
    census = BufferCensus(
        name="soak-device-buffers",
        tolerance_bytes=scenario.thresholds.device_buffer_growth_max_bytes)
    generation = 0  # restarts of the GLOBAL role (compile-drift folds)
    fleet.start()
    try:
        for idx in range(scenario.intervals):
            takeover = (scenario.kind == KIND_KILL_FOREVER
                        and ROLE_GLOBAL in scenario.kills_at(idx))
            if not takeover:
                for role in scenario.kills_at(idx):
                    attempts = fleet.checkpoint(role)
                    ledger.ckpt_retries += max(0, attempts - 1)
                    _fold(ledger, fleet.counters(role), crash=True)
                    fleet.kill_restart(role)
                    ledger.restarts[role] = \
                        ledger.restarts.get(role, 0) + 1
                    if role == ROLE_GLOBAL:
                        generation += 1
                    say(f"interval {idx}: killed+restarted {role} "
                        f"(checkpoint attempts={attempts})")
            mode = scenario.sink_mode(idx)
            fleet.set_sink_mode(mode)
            lines, sent_c, sent_l, n_series = interval_traffic(
                scenario, idx)
            p0 = fleet.local_processed()
            fleet.send(lines)
            ledger.sent_global += sent_c
            ledger.sent_local += sent_l
            _settle(fleet.local_processed, p0 + len(lines))
            i0 = fleet.global_imported()
            ledger.emitted_local += fleet.flush_local()
            _settle(fleet.global_imported, i0 + n_series)
            if takeover:
                emitted, sample = _takeover(scenario, fleet, ledger,
                                            idx, sent_c, say)
                generation += 1  # the standby is a different process
            else:
                emitted, sample = fleet.flush_global()
            ledger.emitted_global += emitted
            audit.snapshot(label=f"interval-{idx}", settled=False)
            if not census.armed and \
                    idx + 1 >= scenario.thresholds.warmup_intervals:
                census.arm(label=f"post-warmup-{idx}")
            else:
                census.sample(label=f"interval-{idx}",
                              programs=("flush_local", "flush_global"))
            monitor.add(IntervalSample(idx=idx, generation=generation,
                                       **sample))
            if mode != MODE_OK or scenario.kills_at(idx):
                say(f"interval {idx}: mode={mode} "
                    f"emitted={emitted:.0f}/{ledger.sent_global}")
        # terminal settlement: clean egress, then flush rounds until
        # nothing new emits and the requeue is drained — late, never
        # lost, and the ledger closes exactly
        fleet.set_sink_mode(MODE_OK)
        for _ in range(12):
            moved = fleet.flush_local()
            time.sleep(0.2)
            emitted, _sample = fleet.flush_global()
            ledger.emitted_local += moved
            ledger.emitted_global += emitted
            if (not moved and not emitted
                    and fleet.counters(ROLE_GLOBAL).get("dd_pending", 0)
                    == 0):
                break
        for role in (ROLE_GLOBAL, ROLE_LOCAL):
            _fold(ledger, fleet.counters(role), crash=False)
        audit.snapshot(label="terminal-settlement", settled=True)
        census.settle(label="terminal-settlement")
    finally:
        fleet.stop()
    ledger.device_buffer_growth_bytes = census.growth_bytes()
    ledger.buffer_census_ok = census.settled_ok()
    if census.violations:
        ledger.buffer_census_detail = str(census.violations[0])
    report = SoakReport(scenario=scenario, ledger=ledger, monitor=monitor)
    report.results = run_gates(scenario, monitor, ledger)
    report.ledger_timeline = audit.timeline()
    report.buffer_timeline = census.timeline()
    if enforce_gates:
        # gates first (their failure message carries the scenario's
        # exact repro call); the audit/census twins are the
        # independent backstops
        enforce(report.results, scenario)
        audit.assert_clean()
        census.assert_clean()
    return report

"""Deterministic soak scenarios: one seed fully determines the chaos.

A :class:`SoakScenario` is the complete, replayable description of a
soak run — how many driven flush intervals, which intervals SIGKILL
which fleet role, which intervals the sink egress is black-holed /
5xx-ing / slow, and which seeded fault kinds ride the servers'
:class:`~veneur_tpu.resilience.faults.FaultInjector` (checkpoint/spool
disk-full, flush-deadline pressure, membership churn). Everything is
derived from ``random.Random(seed)`` in :meth:`SoakScenario.generate`,
so a failed soak reproduces exactly from the seed its gate violation
names (``docs/resilience.md`` "Soak & chaos").

The schedule layout keeps the invariant gates decidable:

* chaos (kills + sink outage windows) lands only in
  ``[warmup, intervals - recovery_tail)`` — the head gives the compile
  ladder and RSS a settling window, the tail gives every breaker /
  overload / requeue excursion room to recover before the recovery
  gate reads the final samples;
* kills cycle global → local → proxy, so three scheduled kills cover
  every fleet role;
* sink windows never extend into the recovery tail, so the one
  repost-per-interval drain always empties the requeue before the end.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Tuple

# sink egress modes a scenario window can impose on the global's
# Datadog POST path (orchestrator.ChaosPost)
MODE_OK = "ok"
MODE_BLACKHOLE = "blackhole"   # connect/refused twin: raises OSError
MODE_HTTP_5XX = "http_5xx"     # API-side failure: returns 503
MODE_SLOW = "slow"             # latency injection: sleeps, then 202
SINK_MODES = (MODE_BLACKHOLE, MODE_HTTP_5XX, MODE_SLOW)

# fleet roles a kill can target, in kill-cycle order (the single-kill
# smoke scenario kills the global: checkpoint restore + sink-generation
# folding is the most load-bearing path)
ROLE_GLOBAL = "global"
ROLE_LOCAL = "local"
ROLE_PROXY = "proxy"
KILL_CYCLE = (ROLE_GLOBAL, ROLE_LOCAL, ROLE_PROXY)
# the warm-standby global (fleet/standby.py): only present in
# kill_forever scenarios, promoted when the active dies
ROLE_STANDBY = "standby"

# scenario kinds: kill_restart is the classic soak (SIGKILL →
# same-port respawn → checkpoint restore); kill_forever is the HA
# acceptance (SIGKILL the active global with NO restart — the
# warm standby must take the lease, merge its replicated shadow, and
# serve, with loss bounded to the active's un-flushed tail)
KIND_KILL_RESTART = "kill_restart"
KIND_KILL_FOREVER = "kill_forever"

# seeded fault kinds the servers arm (resilience/faults.py SOAK_KINDS)
DEFAULT_FAULT_KINDS = "disk_full,deadline_pressure"


@dataclass(frozen=True)
class GateThresholds:
    """The steady-state invariant bounds ``soak.gates`` machine-checks.

    Defaults encode the acceptance bar from docs/resilience.md: exact
    conservation, RSS slope ≤ 1% of the mean per 100 intervals after
    warmup, zero compile-counter drift per process generation,
    timeline coverage ≥ 0.9, bounded end-to-end freshness, and full
    recovery (overload 0, breaker closed, requeue drained, no
    degradations) over the final ``recovery_intervals`` samples."""

    warmup_intervals: int = 2
    rss_slope_pct_per_100: float = 1.0
    coverage_min: float = 0.9
    e2e_age_p99_max_s: float = 60.0
    recovery_intervals: int = 3
    max_compile_drift: int = 0
    requeue_max_bytes: int = 32 * 1048576
    # kill_forever only: wall-clock bound on active-death →
    # standby-holds-the-lease (the lease ttl plus election slack)
    takeover_detect_max_s: float = 15.0
    # settled growth bound for the driver-process BufferCensus
    # (lint/buffer_census.py): max bytes of net jax.live_arrays()
    # growth between arming and the terminal settlement
    device_buffer_growth_max_bytes: int = 1 << 20


@dataclass(frozen=True)
class FaultWindow:
    """One sink-egress outage: ``mode`` holds for intervals
    ``[start, end)``."""

    mode: str
    start: int
    end: int

    def covers(self, idx: int) -> bool:
        return self.start <= idx < self.end


@dataclass(frozen=True)
class SoakScenario:
    """One fully-determined soak run. ``kills`` is a tuple of
    ``(interval_index, role)``; a kill executes BEFORE that interval's
    traffic (checkpoint-commit → SIGKILL → restart on the same ports
    and checkpoint path). ``repro()`` renders the exact call that
    regenerates this scenario — every gate violation carries it."""

    seed: int
    intervals: int
    kills: Tuple[Tuple[int, str], ...] = ()
    sink_windows: Tuple[FaultWindow, ...] = ()
    fault_rate: float = 0.05
    fault_kinds: str = DEFAULT_FAULT_KINDS
    counters_per_interval: int = 24
    timers_per_interval: int = 8
    thresholds: GateThresholds = field(default_factory=GateThresholds)
    kind: str = KIND_KILL_RESTART

    def sink_mode(self, idx: int) -> str:
        for w in self.sink_windows:
            if w.covers(idx):
                return w.mode
        return MODE_OK

    def kills_at(self, idx: int) -> Tuple[str, ...]:
        return tuple(role for at, role in self.kills if at == idx)

    def repro(self) -> str:
        kind = ("" if self.kind == KIND_KILL_RESTART
                else f", kind={self.kind!r}")
        return (f"SoakScenario.generate(seed={self.seed}, "
                f"intervals={self.intervals}, kills={len(self.kills)}"
                f"{kind})")

    @classmethod
    def generate(cls, seed: int, intervals: int = 8, kills: int = 1,
                 thresholds: GateThresholds = None,
                 fault_rate: float = 0.05,
                 fault_kinds: str = DEFAULT_FAULT_KINDS,
                 kind: str = KIND_KILL_RESTART) -> "SoakScenario":
        """Derive the full chaos schedule from ``seed``. Same
        arguments → identical scenario, byte for byte."""
        thr = thresholds or GateThresholds()
        rng = random.Random(seed)
        # chaos may not touch the warmup head or the recovery tail
        lo = thr.warmup_intervals
        hi = max(lo + 1, intervals - (thr.recovery_intervals + 1))
        span = range(lo, hi)
        if kind == KIND_KILL_FOREVER:
            # the HA takeover scenario: exactly ONE kill — the active
            # global, dead forever — and no sink-outage windows (the
            # outage transport is per-process; a window spanning the
            # takeover would impose chaos on a sink generation that no
            # longer exists — orthogonal coverage already owned by the
            # kill_restart scenarios)
            kill_at = rng.choice(list(span))
            return cls(seed=seed, intervals=intervals,
                       kills=((kill_at, ROLE_GLOBAL),), sink_windows=(),
                       fault_rate=fault_rate, fault_kinds=fault_kinds,
                       thresholds=thr, kind=kind)
        n_kills = min(kills, len(span))
        kill_at = sorted(
            # random.Random.sample, not the store's locked sample()
            rng.sample(span, n_kills)  # lint: ok(unlocked-call) random.Random.sample, not the store's locked sample() — a name collision, not a lock bypass
        ) if n_kills else []
        kill_plan = tuple((at, KILL_CYCLE[i % len(KILL_CYCLE)])
                         for i, at in enumerate(kill_at))
        # one window per sink mode, longest first, clipped to the
        # chaos span; windows may overlap kills (a global kill during
        # a black hole is exactly the crash-loss fold the dd-rows gate
        # accounts) but never each other
        windows = []
        taken = set()
        for mode, length in ((MODE_BLACKHOLE, 3), (MODE_HTTP_5XX, 2),
                             (MODE_SLOW, 1)):
            length = min(length, len(span))
            if length <= 0:
                continue
            starts = [s for s in range(lo, hi - length + 1)
                      if not any(t in taken for t in range(s, s + length))]
            if not starts:
                continue
            start = rng.choice(starts)
            taken.update(range(start, start + length))
            windows.append(FaultWindow(mode, start, start + length))
        return cls(seed=seed, intervals=intervals, kills=kill_plan,
                   sink_windows=tuple(sorted(windows,
                                             key=lambda w: w.start)),
                   fault_rate=fault_rate, fault_kinds=fault_kinds,
                   thresholds=thr)

"""Self-tracing: Trace spans, the nonblocking client, metric reporting.

The TPU framework traces itself the way the reference does
(``/root/reference/trace/``): every flush/import/forward can be wrapped
in a ``Trace`` span recorded through a ``Client`` into either an
upstream veneur (UDP/UNIX SSF) or the server's own span channel.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional

from veneur_tpu.protocol.gen.ssf import sample_pb2
from veneur_tpu.trace import samples as ssf_samples
from veneur_tpu.trace.client import (Client, FlushError, NoClientError,
                                     WouldBlockError, flush, flush_async,
                                     neutralize_client, new_backend_client,
                                     new_channel_client, record,
                                     send_client_statistics)

# Tag keys (trace/trace.go:43-53)
RESOURCE_KEY = "resource"
ERROR_MESSAGE_TAG = "error.msg"
ERROR_TYPE_TAG = "error.type"
ERROR_STACK_TAG = "error.stack"

# The service name stamped on every span (trace/trace.go's package var)
SERVICE = ""

# The default client used by module-level recording (client.go:414-421)
default_client: Optional[Client] = None

_disabled = False
_disabled_lock = threading.Lock()


def enable() -> None:
    global _disabled
    with _disabled_lock:
        _disabled = False


def disable() -> None:
    global _disabled
    with _disabled_lock:
        _disabled = True


def disabled() -> bool:
    with _disabled_lock:
        return _disabled


def set_default_client(client: Optional[Client]) -> None:
    """Swap the default client, closing the old one (client.go:392-402)."""
    global default_client
    old = default_client
    default_client = client
    if old is not None:
        old.close()


class Trace:
    """A span under construction (trace/trace.go:58-96)."""

    def __init__(self, trace_id: int = 0, span_id: int = 0,
                 parent_id: int = 0, resource: str = "", name: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.resource = resource
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.status = sample_pb2.SSFSample.OK
        self.tags: Dict[str, str] = {}
        self.samples = []
        self._error = False
        self.indicator = False

    # -- construction -------------------------------------------------------

    @classmethod
    def start_trace(cls, resource: str) -> "Trace":
        """Root span: trace id == span id (trace.go:302-317)."""
        tid = random.getrandbits(63)
        return cls(trace_id=tid, span_id=tid, parent_id=0, resource=resource)

    def start_child_span(self) -> "Trace":
        """A child span of this one (trace.go:319-330)."""
        child = Trace(trace_id=self.trace_id,
                      span_id=random.getrandbits(63),
                      parent_id=self.span_id, resource=self.resource)
        return child

    # -- recording ----------------------------------------------------------

    def finish(self) -> None:
        if self.end is None:
            self.end = time.time()

    @property
    def duration(self) -> float:
        return -1.0 if self.end is None else self.end - self.start

    def error(self, exc: BaseException) -> None:
        """Mark errored with the standard error tags (trace.go:207-224)."""
        self.status = sample_pb2.SSFSample.CRITICAL
        self._error = True
        self.tags[ERROR_MESSAGE_TAG] = str(exc)
        self.tags[ERROR_TYPE_TAG] = type(exc).__name__ or "error"
        self.tags[ERROR_STACK_TAG] = str(exc)

    def add(self, *samples) -> None:
        self.samples.extend(samples)

    def ssf_span(self) -> sample_pb2.SSFSpan:
        """Convert to the wire form; sets duration from start/end
        (trace.go:139-161)."""
        span = sample_pb2.SSFSpan(
            start_timestamp=int(self.start * 1e9),
            end_timestamp=int((self.end if self.end is not None
                               else self.start) * 1e9),
            error=self._error,
            trace_id=self.trace_id, id=self.span_id,
            parent_id=self.parent_id,
            name=self.name, service=SERVICE, indicator=self.indicator)
        for k, v in self.tags.items():
            span.tags[k] = v
        if self.resource:
            span.tags[RESOURCE_KEY] = self.resource
        span.metrics.extend(self.samples)
        return span

    def client_record(self, cl: Optional[Client], name: str = "",
                      tags: Optional[Dict[str, str]] = None) -> None:
        """Finish and submit on a client (trace.go:181-205). Never raises
        for backpressure: a full client drops the span."""
        self.tags.update(tags or {})
        self.finish()
        span = self.ssf_span()
        if name:
            span.name = name
        try:
            record(cl, span)
        except (NoClientError, WouldBlockError):
            pass

    def record(self, name: str = "",
               tags: Optional[Dict[str, str]] = None) -> None:
        self.client_record(default_client, name, tags)

    # -- propagation --------------------------------------------------------

    def context_as_parent(self) -> Dict[str, str]:
        """Baggage headers for cross-process propagation
        (trace.go:290-299, opentracing inject/extract)."""
        return {"traceid": str(self.trace_id),
                "parentid": str(self.span_id),
                RESOURCE_KEY: self.resource}


def from_headers(headers: Dict[str, str], resource: str = "") -> Trace:
    """Rebuild a child span from propagated baggage (the opentracing
    extract path, trace/opentracing.go)."""
    t = Trace(resource=headers.get(RESOURCE_KEY, resource) or resource)
    try:
        t.trace_id = int(headers.get("traceid", "0"))
        t.parent_id = int(headers.get("parentid", "0"))
    except ValueError:
        pass
    if not t.trace_id:
        t.trace_id = random.getrandbits(63)
    t.span_id = random.getrandbits(63)
    return t

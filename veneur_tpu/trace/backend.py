"""Trace-client backends: UDP packet + buffered UNIX stream with backoff.

Behavioral port of ``/root/reference/trace/backend.go``:

- ``PacketBackend`` sends each span as one bare protobuf datagram
  (backend.go:94-125); no buffering, no flush.
- ``StreamBackend`` writes framed SSF onto a (UNIX or TCP) stream
  through an optional buffer; a framing error poisons the connection,
  which is closed and re-dialed on the next send — the span itself is
  dropped ("poison pill" resilience, backend.go:72-84,183-240).
- ``connect`` retries with linearly increasing backoff up to a cap,
  bounded by an overall connect timeout (backend.go:135-180).

Defaults (backend.go:20-37): backoff 10 ms, max backoff 1 s, connect
timeout 10 s.
"""

from __future__ import annotations

import io
import logging
import socket
import time
from typing import Optional

from veneur_tpu.protocol import addr as vaddr
from veneur_tpu.protocol import wire

log = logging.getLogger("veneur.trace.backend")

DEFAULT_BACKOFF = 0.010
DEFAULT_MAX_BACKOFF = 1.0
DEFAULT_CONNECT_TIMEOUT = 10.0


class BackendParams:
    def __init__(self, address: str, backoff: float = 0.0,
                 max_backoff: float = 0.0, connect_timeout: float = 0.0,
                 buffer_size: int = 0):
        self.address = address
        self.backoff = backoff or DEFAULT_BACKOFF
        self.max_backoff = max_backoff or DEFAULT_MAX_BACKOFF
        self.connect_timeout = connect_timeout or DEFAULT_CONNECT_TIMEOUT
        self.buffer_size = buffer_size


def _dial(params: BackendParams) -> socket.socket:
    """Dial with linear backoff until the connect timeout elapses
    (backend.go:135-180)."""
    resolved = vaddr.resolve_addr(params.address)
    deadline = time.monotonic() + params.connect_timeout
    wait = 0.0
    while True:
        try:
            return _dial_once(resolved)
        except OSError:
            now = time.monotonic()
            if now >= deadline:
                raise
            time.sleep(min(wait, max(deadline - now, 0.0)))
            wait += params.backoff
            if wait > params.max_backoff:
                wait = params.max_backoff


def _dial_once(resolved: vaddr.ResolvedAddr) -> socket.socket:
    s = socket.socket(resolved.socket_family, resolved.socket_type)
    try:
        s.connect(resolved.connect_target())
    except OSError:
        s.close()
        raise
    return s


class PacketBackend:
    """UDP: one span protobuf per datagram (backend.go:94-125)."""

    def __init__(self, params: BackendParams):
        self.params = params
        self._conn: Optional[socket.socket] = None

    def send_sync(self, span) -> None:
        if self._conn is None:
            self._conn = _dial(self.params)
        self._conn.send(span.SerializeToString())

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class StreamBackend:
    """Framed SSF over a stream, optionally buffered
    (backend.go:128-240)."""

    def __init__(self, params: BackendParams):
        self.params = params
        self._conn: Optional[socket.socket] = None
        self._buffer: Optional[io.BytesIO] = None

    def _connect(self) -> None:
        self._conn = _dial(self.params)
        if self.params.buffer_size > 0:
            self._buffer = io.BytesIO()

    def send_sync(self, span) -> None:
        if self._conn is None:
            self._connect()
        frame = wire.frame_bytes(span)
        if self._buffer is not None:
            self._buffer.write(frame)
            if self._buffer.tell() >= self.params.buffer_size:
                self.flush_sync()
            return
        try:
            self._conn.sendall(frame)
        except OSError:
            # poison-pill resilience: drop the span, reconnect next send
            # (backend.go:72-84,216-223)
            self._teardown()
            raise

    def flush_sync(self) -> None:
        """Flush the buffer; a failed flush discards it and forces a
        reconnect (backend.go:226-240)."""
        if self._buffer is None:
            return
        if self._conn is None:
            self._connect()
        data = self._buffer.getvalue()
        self._buffer = io.BytesIO()
        if not data:
            return
        try:
            self._conn.sendall(data)
        except OSError:
            self._teardown()
            raise

    def _teardown(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        self._conn = None
        if self.params.buffer_size > 0:
            self._buffer = io.BytesIO()

    def close(self) -> None:
        self._teardown()

"""The nonblocking trace client: a bounded span pump with backpressure.

Behavioral port of ``/root/reference/trace/client.go``:

- ``Client`` owns a bounded queue of spans and N backend worker threads
  draining it (client.go:56-117, DefaultCapacity 64 / DefaultParallelism
  8, :425-430).
- ``record`` never blocks: a full queue returns ``WouldBlockError`` and
  bumps ``failed_records`` (client.go:459-479).
- ``flush``/``flush_async`` ask every flushable backend to flush its
  buffer and aggregate errors (client.go:489-543).
- ``ChannelClient`` delivers spans straight into an in-process queue —
  how veneur feeds its own SpanChan (client.go:369-390, server.go:196-202);
  ``neutralize_client`` makes every operation fail fast for tests
  (client.go:404-412).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, List, Optional

from veneur_tpu.protocol import addr as vaddr
from veneur_tpu.trace.backend import (BackendParams, PacketBackend,
                                      StreamBackend)

log = logging.getLogger("veneur.trace.client")

DEFAULT_CAPACITY = 64
DEFAULT_PARALLELISM = 8
DEFAULT_VENEUR_ADDRESS = "udp://127.0.0.1:8128"


class NoClientError(Exception):
    """client is not initialized (client.go:441)."""


class WouldBlockError(Exception):
    """sending span would block (client.go:445)."""


class FlushError(Exception):
    """One or more backends failed to flush (client.go:498-506)."""

    def __init__(self, errors: List[BaseException]):
        super().__init__(f"Errors encountered flushing backends: {errors}")
        self.errors = errors


class Client:
    """A span pump over networked backends (client.go:298-343)."""

    def __init__(self, address: Optional[str] = None,
                 capacity: int = DEFAULT_CAPACITY,
                 parallelism: int = DEFAULT_PARALLELISM,
                 backoff: float = 0.0, max_backoff: float = 0.0,
                 connect_timeout: float = 0.0, buffered: bool = False,
                 buffer_size: int = 0,
                 backends: Optional[List] = None,
                 span_queue: Optional["queue.Queue"] = None):
        self._records: Optional["queue.Queue"] = None
        self._spans: Optional["queue.Queue"] = span_queue
        self._backends: List = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.failed_flushes = 0
        self.successful_flushes = 0
        self.failed_records = 0
        self.successful_records = 0

        if span_queue is not None:
            return  # channel client: no backends, no workers

        if backends is None:
            if address is None:
                address = DEFAULT_VENEUR_ADDRESS
            resolved = vaddr.resolve_addr(address)
            params = BackendParams(
                address, backoff=backoff, max_backoff=max_backoff,
                connect_timeout=connect_timeout,
                buffer_size=buffer_size if (buffered or buffer_size) else 0)
            if resolved.family == "udp":
                backends = [PacketBackend(params)
                            for _ in range(parallelism)]
            else:
                backends = [StreamBackend(params)
                            for _ in range(parallelism)]
        self._backends = backends
        self._records = queue.Queue(maxsize=max(1, capacity))
        for backend in self._backends:
            t = threading.Thread(target=self._run_backend, args=(backend,),
                                 name="trace-client", daemon=True)
            t.start()
            self._threads.append(t)

    def _run_backend(self, backend) -> None:
        """Worker loop (client.go:96-117)."""
        while not self._stop.is_set():
            try:
                op = self._records.get(timeout=0.2)
            except queue.Empty:
                continue
            span, done, flush_to = op
            try:
                if flush_to is not None:
                    flush_sync = getattr(backend, "flush_sync", None)
                    if flush_sync is not None:
                        flush_sync()
                    flush_to.put(None)
                else:
                    backend.send_sync(span)
                    if done is not None:
                        done.put(None)
            except Exception as e:
                target = flush_to if flush_to is not None else done
                if target is not None:
                    target.put(e)

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        for b in self._backends:
            try:
                b.close()
            except OSError:
                pass


def record(cl: Optional[Client], span, done: Optional["queue.Queue"] = None):
    """Nonblocking submit (client.go:459-479). Raises NoClientError /
    WouldBlockError."""
    if cl is None:
        raise NoClientError("client is not initialized")
    if cl._spans is not None:
        try:
            cl._spans.put_nowait(span)
        except queue.Full:
            with cl._lock:
                cl.failed_records += 1
            raise WouldBlockError("sending span would block")
        with cl._lock:
            cl.successful_records += 1
        if done is not None:
            done.put(None)
        return
    if cl._records is None:
        with cl._lock:
            cl.failed_records += 1
        raise WouldBlockError("sending span would block")
    try:
        cl._records.put_nowait((span, done, None))
    except queue.Full:
        with cl._lock:
            cl.failed_records += 1
        raise WouldBlockError("sending span would block")
    with cl._lock:
        cl.successful_records += 1


def flush(cl: Optional[Client], timeout: float = 10.0) -> None:
    """Synchronous flush of all flushable backends (client.go:489-496)."""
    if cl is None:
        raise NoClientError("client is not initialized")
    errors: List[BaseException] = []
    if cl._records is not None:
        for backend in cl._backends:
            if getattr(backend, "flush_sync", None) is None:
                continue
            ch: "queue.Queue" = queue.Queue(1)
            try:
                cl._records.put_nowait((None, None, ch))
            except queue.Full:
                errors.append(WouldBlockError("sending span would block"))
                continue
            try:
                err = ch.get(timeout=timeout)
                if err is not None:
                    errors.append(err)
            except queue.Empty:
                errors.append(TimeoutError("flush timed out"))
    if errors:
        with cl._lock:
            cl.failed_flushes += 1
        raise FlushError(errors)
    with cl._lock:
        cl.successful_flushes += 1


def flush_async(cl: Optional[Client],
                callback: Optional[Callable] = None) -> None:
    """Fire-and-forget flush (client.go:508-543)."""
    if cl is None:
        raise NoClientError("client is not initialized")

    def run():
        try:
            flush(cl)
            if callback is not None:
                callback(None)
        except Exception as e:
            if callback is not None:
                callback(e)

    threading.Thread(target=run, daemon=True).start()


def new_channel_client(span_queue: "queue.Queue", **kw) -> Client:
    """A client delivering into an in-process queue (client.go:369-390)."""
    return Client(span_queue=span_queue, **kw)


def new_backend_client(backend, capacity: int = 1, **kw) -> Client:
    """A client over one injected backend (client.go:346-366)."""
    return Client(backends=[backend], capacity=capacity, **kw)


def neutralize_client(cl: Client) -> None:
    """Dash all hope of recording or flushing (client.go:404-412)."""
    cl.close()
    cl._records = None
    cl._spans = None
    cl._backends = []


def send_client_statistics(cl: Client, report: Callable[[str, float], None],
                           ) -> None:
    """Report + reset backpressure counters (client.go:446-452)."""
    with cl._lock:
        stats = (("trace_client.flushes_failed_total", cl.failed_flushes),
                 ("trace_client.flushes_succeeded_total",
                  cl.successful_flushes),
                 ("trace_client.records_failed_total", cl.failed_records),
                 ("trace_client.records_succeeded_total",
                  cl.successful_records))
        cl.failed_flushes = cl.successful_flushes = 0
        cl.failed_records = cl.successful_records = 0
    for name, value in stats:
        report(name, float(value))

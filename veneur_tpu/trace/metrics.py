"""One-off metric reporting through a trace client.

Port of ``/root/reference/trace/metrics/client.go:21-58``: batches of
SSF samples ride in a metrics-only SSF span.
"""

from __future__ import annotations

from typing import List, Optional

from veneur_tpu.protocol.gen.ssf import sample_pb2
from veneur_tpu.trace.client import Client, record
from veneur_tpu.trace.samples import Samples


class NoMetricsError(Exception):
    """No metrics were included in the batch (metrics/client.go:12-16)."""


def report(cl: Optional[Client], samples: Samples) -> None:
    report_batch(cl, samples.batch)


def report_batch(cl: Optional[Client],
                 samples: List[sample_pb2.SSFSample]) -> None:
    if not samples:
        raise NoMetricsError("No metrics to send.")
    span = sample_pb2.SSFSpan()
    span.metrics.extend(samples)
    record(cl, span)


def report_one(cl: Optional[Client], metric: sample_pb2.SSFSample) -> None:
    report_batch(cl, [metric])

"""OpenTracing-compatible layer over the SSF trace core.

The reference ships an opentracing.Tracer implementation
(``/root/reference/trace/opentracing.go``) so applications written
against the OpenTracing API emit SSF spans; ``http/http.go:184-188``
uses its inject/extract for forward-request propagation. This is the
Python equivalent: the classic ``Tracer`` / ``Span`` / ``SpanContext``
trio with TextMap/HTTP-headers inject-extract, backed by
``veneur_tpu.trace.Trace``. Only the surface veneur itself exercises is
implemented — not the full semantic-conventions catalogue.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from veneur_tpu import trace as vtrace

FORMAT_TEXT_MAP = "text_map"
FORMAT_HTTP_HEADERS = "http_headers"


class SpanContext:
    """Propagation-relevant identity of a span (opentracing.go:58-76)."""

    def __init__(self, trace_id: int, span_id: int, resource: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.resource = resource

    def baggage(self) -> Dict[str, str]:
        return {"traceid": str(self.trace_id),
                "parentid": str(self.span_id),
                vtrace.RESOURCE_KEY: self.resource}


class Span:
    """An OpenTracing span wrapping a Trace (opentracing.go:78-170)."""

    def __init__(self, tracer: "Tracer", trace: "vtrace.Trace"):
        self._tracer = tracer
        self._trace = trace
        self._tags: Dict[str, str] = {}
        self._finished = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self._trace.trace_id, self._trace.span_id,
                           self._trace.resource)

    def set_operation_name(self, name: str) -> "Span":
        self._trace.name = name
        return self

    def set_tag(self, key: str, value) -> "Span":
        self._tags[key] = str(value)
        return self

    def log_kv(self, kv: Dict[str, str]) -> "Span":
        for k, v in kv.items():
            self.set_tag(f"log.{k}", v)
        return self

    def finish(self, finish_time: Optional[float] = None):
        if self._finished:  # explicit finish inside a with-block
            return
        self._finished = True
        self._trace.finish()
        if finish_time is not None:
            self._trace.end = finish_time
        self._trace.client_record(self._tracer.client,
                                  tags=self._tags or None)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self._trace.error(exc)
        self.finish()


class Tracer:
    """start_span / inject / extract (opentracing.go:172-280)."""

    def __init__(self, client=None):
        self.client = client

    def start_span(self, operation_name: str,
                   child_of: Optional[SpanContext] = None,
                   start_time: Optional[float] = None) -> Span:
        if child_of is not None:
            ctx = (child_of.context if isinstance(child_of, Span)
                   else child_of)
            import random

            t = vtrace.Trace(resource=ctx.resource or operation_name)
            t.trace_id = ctx.trace_id
            t.parent_id = ctx.span_id
            t.span_id = random.getrandbits(63)
        else:
            t = vtrace.Trace.start_trace(operation_name)
        t.name = operation_name
        if start_time is not None:
            t.start = start_time
        else:
            t.start = time.time()
        return Span(self, t)

    def inject(self, span_context: SpanContext, format: str,
               carrier: Dict[str, str]):
        if format not in (FORMAT_TEXT_MAP, FORMAT_HTTP_HEADERS):
            raise ValueError(f"unsupported carrier format {format!r}")
        carrier.update(span_context.baggage())

    def extract(self, format: str,
                carrier: Dict[str, str]) -> Optional[SpanContext]:
        if format not in (FORMAT_TEXT_MAP, FORMAT_HTTP_HEADERS):
            raise ValueError(f"unsupported carrier format {format!r}")
        lowered = {k.lower(): v for k, v in carrier.items()}
        try:
            trace_id = int(lowered.get("traceid", "0"))
            span_id = int(lowered.get("parentid", "0"))
        except ValueError:
            return None
        if not trace_id:
            return None
        return SpanContext(trace_id, span_id,
                           lowered.get(vtrace.RESOURCE_KEY, ""))


_global_tracer = Tracer()


def set_global_tracer(tracer: Tracer):
    global _global_tracer
    _global_tracer = tracer


def global_tracer() -> Tracer:
    return _global_tracer

"""OpenTracing-compatible layer over the SSF trace core.

The reference ships a complete opentracing-go implementation
(``/root/reference/trace/opentracing.go``) so third-party code written
against the OpenTracing API emits SSF spans through veneur's tracer —
not just veneur's own forward-path propagation. This is the Python
re-expression of that full surface:

* ``Tracer.start_span`` with ``child_of`` / ``references``
  (child-of and follows-from are treated identically, as the reference
  does — opentracing.go:384-426), tags, explicit start time, and an
  implicit active-span parent (the contextvars analogue of the Go
  ``Span.Attach(ctx)`` / ``context.Context`` plumbing).
* ``SpanContext`` carrying arbitrary baggage items with
  case-insensitive int64 parsing for traceid/parentid/spanid
  (opentracing.go:109-181).
* Standard tag/log mapping: the ``error`` tag marks the SSF span
  errored; the ``name`` tag overrides the span name
  (opentracing.go:446-452); ``log_kv``/``log_fields`` record log
  lines (reported as ``log.*`` tags — the reference parks them
  unreported, opentracing.go:293-303; recording them is this build's
  one deliberate improvement).
* Inject/extract over TEXT_MAP and HTTP_HEADERS carriers plus the
  BINARY format (an SSF span protobuf, opentracing.go:501-601), with
  the reference's multi-dialect header support on extract: Envoy,
  OpenTracing, Ruby, and veneur header pairs are tried in that order
  (opentracing.go:29-52).
* A process-global tracer, registered at import like the reference's
  ``init()`` (opentracing.go:53-58).

Deviations, deliberate: ``extract`` returns ``None`` on a parse
failure instead of a Go-style error value (Python-idiomatic; callers
on the forward path treat "no parent" as "start a root"), and a root
span's name defaults to the operation name rather than the calling
function's name (the reference's ``runtime.Caller`` default is a
Go-ism; the ``name`` tag override is supported either way).
"""

from __future__ import annotations

import contextvars
import random
import time
from typing import Any, Dict, List, Optional, Tuple, Union

from veneur_tpu import trace as vtrace

FORMAT_TEXT_MAP = "text_map"
FORMAT_HTTP_HEADERS = "http_headers"
FORMAT_BINARY = "binary"

# Tried in order on extract; first pair with a nonzero id wins
# (opentracing.go:29-52: Envoy sits nearest, so it goes first).
HEADER_FORMATS: List[Tuple[str, str]] = [
    ("x-request-id", "x-client-trace-id"),   # Envoy
    ("trace-id", "span-id"),                 # OpenTracing
    ("x-trace-id", "x-span-id"),             # Ruby
    ("traceid", "spanid"),                   # veneur
]

REF_CHILD_OF = "child_of"
REF_FOLLOWS_FROM = "follows_from"


class Reference:
    """A causal reference to another span's context
    (opentracing.go:412-426: child-of and follows-from are merged the
    same way)."""

    __slots__ = ("type", "referenced_context")

    def __init__(self, type: str, referenced_context: "SpanContext"):
        self.type = type
        self.referenced_context = referenced_context


def child_of(ctx: Union["SpanContext", "Span"]) -> Reference:
    return Reference(REF_CHILD_OF, _as_context(ctx))


def follows_from(ctx: Union["SpanContext", "Span"]) -> Reference:
    return Reference(REF_FOLLOWS_FROM, _as_context(ctx))


def _as_context(obj) -> "SpanContext":
    return obj.context if isinstance(obj, Span) else obj


class SpanContext:
    """Propagation-relevant identity of a span: a bag of baggage items
    with case-insensitive int64 views for the ids
    (opentracing.go:109-181)."""

    def __init__(self, trace_id: int = 0, span_id: int = 0,
                 resource: str = "",
                 baggage_items: Optional[Dict[str, str]] = None):
        self.baggage_items: Dict[str, str] = dict(baggage_items or {})
        if trace_id:
            self.baggage_items.setdefault("traceid", str(trace_id))
        if span_id:
            self.baggage_items.setdefault("spanid", str(span_id))
            self.baggage_items.setdefault("parentid", str(span_id))
        if resource:
            self.baggage_items.setdefault(vtrace.RESOURCE_KEY, resource)

    def _int_item(self, key: str) -> int:
        for k, v in self.baggage_items.items():
            if k.lower() == key:
                try:
                    return int(v)
                except ValueError:
                    return 0
        return 0

    @property
    def trace_id(self) -> int:
        return self._int_item("traceid")

    @property
    def span_id(self) -> int:
        return self._int_item("spanid") or self._int_item("parentid")

    @property
    def parent_id(self) -> int:
        return self._int_item("parentid")

    @property
    def resource(self) -> str:
        for k, v in self.baggage_items.items():
            if k.lower() == vtrace.RESOURCE_KEY:
                return v
        return ""

    def with_baggage_item(self, key: str, value: str) -> "SpanContext":
        items = dict(self.baggage_items)
        items[key] = value
        return SpanContext(baggage_items=items)

    def foreach_baggage_item(self, handler) -> None:
        """Call ``handler(k, v)`` per item; a falsy return stops the
        iteration (opentracing.go:120-132)."""
        for k, v in self.baggage_items.items():
            if not handler(k, v):
                return

    def baggage(self) -> Dict[str, str]:
        return dict(self.baggage_items)


class Span:
    """An OpenTracing span wrapping a Trace (opentracing.go:183-334)."""

    def __init__(self, tracer: "Tracer", trace: "vtrace.Trace"):
        self._tracer = tracer
        self._trace = trace
        self._tags: Dict[str, str] = {}
        self._baggage: Dict[str, str] = {}
        self._log_lines: List[Dict[str, str]] = []
        self._error = False
        self._finished = False

    @property
    def context(self) -> SpanContext:
        items = {"traceid": str(self._trace.trace_id),
                 "spanid": str(self._trace.span_id),
                 "parentid": str(self._trace.span_id),
                 vtrace.RESOURCE_KEY: self._trace.resource}
        items.update(self._baggage)
        return SpanContext(baggage_items=items)

    @property
    def tracer(self) -> "Tracer":
        return self._tracer

    def set_operation_name(self, name: str) -> "Span":
        # the reference points SetOperationName at the trace's
        # *resource* (opentracing.go:259-262); the span name rides the
        # "name" tag. Keep both coherent for the common rename case.
        self._trace.resource = name
        self._trace.name = name
        return self

    def set_tag(self, key: str, value: Any) -> "Span":
        # standard-tag mapping: "error" flags the SSF span errored,
        # "name" renames it (opentracing.go:446-452 + samples.go
        # error indicator)
        if key == "error":
            self._error = bool(value) and str(value).lower() != "false"
            return self
        val = value if isinstance(value, str) else str(value)
        if key == "name":
            self._trace.name = val
        self._tags[key] = val
        return self

    def log_kv(self, kv: Dict[str, Any]) -> "Span":
        self._log_lines.append({k: str(v) for k, v in kv.items()})
        for k, v in kv.items():
            self._tags.setdefault(f"log.{k}", str(v))
        return self

    # opentracing-python calls the structured form log_fields; the
    # reference parks both in s.logLines (opentracing.go:293-303)
    log_fields = log_kv

    def set_baggage_item(self, key: str, value: str) -> "Span":
        self._baggage[key] = value
        return self

    def baggage_item(self, key: str) -> Optional[str]:
        return self._baggage.get(key)

    def finish(self, finish_time: Optional[float] = None,
               log_records: Optional[List[Dict[str, Any]]] = None):
        if self._finished:  # explicit finish inside a with-block
            return
        self._finished = True
        for rec in log_records or ():
            self.log_kv(rec)
        if self._error:
            # the standard "error" tag (set_tag path): flag the SSF
            # span errored without synthesizing an exception
            self._trace.status = vtrace.sample_pb2.SSFSample.CRITICAL
            self._trace._error = True
        self._trace.finish()
        if finish_time is not None:
            self._trace.end = finish_time
        self._trace.client_record(self._tracer.client,
                                  tags=self._tags or None)

    # FinishWithOptions under its opentracing-python spelling
    def finish_with_options(self, finish_time: Optional[float] = None,
                            log_records=None):
        self.finish(finish_time, log_records)

    def attach(self):
        """Make this span the implicit parent for spans started without
        an explicit reference — the contextvars analogue of the
        reference's ``Span.Attach(ctx)`` (opentracing.go:287-291).
        Returns a token for ``detach``; also usable via ``with
        span.attach_scope():``."""
        return _ACTIVE_SPAN.set(self)

    def detach(self, token) -> None:
        _ACTIVE_SPAN.reset(token)

    def attach_scope(self):
        return _ActiveScope(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self._trace.error(exc)
        self.finish()


_ACTIVE_SPAN: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("veneur_active_span", default=None)


def active_span() -> Optional[Span]:
    return _ACTIVE_SPAN.get()


class _ActiveScope:
    def __init__(self, span: Span):
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._token = self._span.attach()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        self._span.detach(self._token)


class Tracer:
    """start_span / inject / extract (opentracing.go:336-601)."""

    def __init__(self, client=None):
        self.client = client

    def start_span(self, operation_name: str = "",
                   child_of: Optional[Union[SpanContext, Span]] = None,
                   references: Optional[List[Reference]] = None,
                   tags: Optional[Dict[str, Any]] = None,
                   start_time: Optional[float] = None,
                   ignore_active_span: bool = False) -> Span:
        refs = list(references or ())
        if child_of is not None:
            refs.insert(0, Reference(REF_CHILD_OF, _as_context(child_of)))
        if not refs and not ignore_active_span:
            implicit = active_span()
            if implicit is not None:
                refs = [Reference(REF_CHILD_OF, implicit.context)]

        if not refs:
            t = vtrace.Trace.start_trace(operation_name)
        else:
            # child-of and follows-from merge identically
            # (opentracing.go:412-426): last reference with a usable
            # context wins, matching the reference's loop order
            parent_ctx = None
            for ref in refs:
                if ref.type in (REF_CHILD_OF, REF_FOLLOWS_FROM) and \
                        isinstance(ref.referenced_context, SpanContext):
                    parent_ctx = ref.referenced_context
            if parent_ctx is None:
                t = vtrace.Trace.start_trace(operation_name)
            else:
                t = vtrace.Trace(
                    resource=parent_ctx.resource or operation_name)
                t.trace_id = parent_ctx.trace_id
                t.parent_id = parent_ctx.span_id
                t.span_id = random.getrandbits(63)
        t.name = operation_name
        t.start = start_time if start_time is not None else time.time()
        span = Span(self, t)
        for k, v in (tags or {}).items():
            span.set_tag(k, v)
        return span

    def inject(self, span_context: Union[SpanContext, Span], format: str,
               carrier) -> None:
        ctx = _as_context(span_context)
        if format in (FORMAT_TEXT_MAP, FORMAT_HTTP_HEADERS):
            try:
                for k, v in ctx.baggage_items.items():
                    carrier[k] = v
            except TypeError as e:
                raise ValueError(
                    f"carrier is not a mutable mapping: {e}") from e
            return
        if format == FORMAT_BINARY:
            # the binary carrier is an SSF span protobuf
            # (opentracing.go:513-531)
            span = vtrace.sample_pb2.SSFSpan()
            span.trace_id = ctx.trace_id
            span.id = ctx.span_id
            span.parent_id = ctx.parent_id
            if ctx.resource:
                span.tags[vtrace.RESOURCE_KEY] = ctx.resource
            try:
                carrier.write(span.SerializeToString())
            except AttributeError as e:
                raise ValueError(
                    f"binary carrier is not writable: {e}") from e
            return
        raise ValueError(f"unsupported carrier format {format!r}")

    def extract(self, format: str, carrier) -> Optional[SpanContext]:
        if format in (FORMAT_TEXT_MAP, FORMAT_HTTP_HEADERS):
            try:
                lowered = {k.lower(): v for k, v in carrier.items()}
            except AttributeError as e:
                raise ValueError(
                    f"carrier is not a mapping: {e}") from e
            trace_id = span_id = 0
            for tkey, skey in HEADER_FORMATS:
                try:
                    trace_id = int(lowered.get(tkey, "0") or "0")
                except ValueError:
                    trace_id = 0
                try:
                    span_id = int(lowered.get(skey, "0") or "0")
                except ValueError:
                    span_id = 0
                if trace_id and span_id:
                    break
            # the veneur wire dialect historically sends traceid +
            # parentid (trace/__init__.py:158-163); accept it so both
            # in-house carriers round-trip
            if not span_id:
                try:
                    span_id = int(lowered.get("parentid", "0") or "0")
                except ValueError:
                    span_id = 0
            if not trace_id:
                return None
            if not span_id:
                return None
            return SpanContext(
                trace_id, span_id,
                lowered.get(vtrace.RESOURCE_KEY, ""))
        if format == FORMAT_BINARY:
            try:
                data = carrier.read()
            except AttributeError as e:
                raise ValueError(
                    f"binary carrier is not readable: {e}") from e
            span = vtrace.sample_pb2.SSFSpan()
            try:
                span.ParseFromString(data)
            except Exception:
                return None
            if not span.trace_id:
                return None
            return SpanContext(span.trace_id, span.id,
                               span.tags.get(vtrace.RESOURCE_KEY, ""))
        raise ValueError(f"unsupported carrier format {format!r}")


# the reference registers its GlobalTracer at package init
# (opentracing.go:53-58)
GlobalTracer = Tracer()
_global_tracer = GlobalTracer


def set_global_tracer(tracer: Tracer):
    global _global_tracer
    _global_tracer = tracer


def global_tracer() -> Tracer:
    return _global_tracer

"""SSF sample constructors (``/root/reference/ssf/samples.go:136-205``).

``count/gauge/histogram/set_sample/timing/status`` build ``SSFSample``
protobufs with ``sample_rate=1`` and the global ``NAME_PREFIX``
prepended (samples.go:100-106); ``randomly_sample`` thins a batch and
scales the surviving samples' rates (samples.go:112-134).
"""

from __future__ import annotations

import random
import time as time_mod
from typing import Dict, List, Optional

from veneur_tpu.protocol.gen.ssf import sample_pb2

# Prefix prepended to every generated sample name (samples.go:35-39);
# veneur sets it to "veneur." for its own internal metrics.
NAME_PREFIX = ""

OK = sample_pb2.SSFSample.OK
WARNING = sample_pb2.SSFSample.WARNING
CRITICAL = sample_pb2.SSFSample.CRITICAL
UNKNOWN = sample_pb2.SSFSample.UNKNOWN


class Samples:
    """A batch of samples to report together (samples.go:23-32)."""

    def __init__(self):
        self.batch: List[sample_pb2.SSFSample] = []

    def add(self, *samples: sample_pb2.SSFSample) -> None:
        self.batch.extend(samples)


def _create(metric, name: str, value: float = 0.0,
            tags: Optional[Dict[str, str]] = None, message: str = "",
            unit: str = "", status=None,
            timestamp: Optional[int] = None) -> sample_pb2.SSFSample:
    s = sample_pb2.SSFSample(metric=metric, name=NAME_PREFIX + name,
                             value=value, message=message, unit=unit,
                             sample_rate=1.0)
    if status is not None:
        s.status = status
    if timestamp is not None:
        s.timestamp = timestamp
    for k, v in (tags or {}).items():
        s.tags[k] = v
    return s


def count(name: str, value: float,
          tags: Optional[Dict[str, str]] = None, **kw) -> sample_pb2.SSFSample:
    return _create(sample_pb2.SSFSample.COUNTER, name, value, tags, **kw)


def gauge(name: str, value: float,
          tags: Optional[Dict[str, str]] = None, **kw) -> sample_pb2.SSFSample:
    return _create(sample_pb2.SSFSample.GAUGE, name, value, tags, **kw)


def histogram(name: str, value: float,
              tags: Optional[Dict[str, str]] = None,
              **kw) -> sample_pb2.SSFSample:
    return _create(sample_pb2.SSFSample.HISTOGRAM, name, value, tags, **kw)


def set_sample(name: str, value: str,
               tags: Optional[Dict[str, str]] = None,
               **kw) -> sample_pb2.SSFSample:
    """A set-membership sample; the member rides in ``message``
    (samples.go:176-186)."""
    return _create(sample_pb2.SSFSample.SET, name, 0.0, tags,
                   message=value, **kw)


def timing(name: str, seconds: float,
           tags: Optional[Dict[str, str]] = None,
           resolution: float = 1e-9, **kw) -> sample_pb2.SSFSample:
    """A timer expressed in ``resolution`` units (default nanoseconds,
    matching the reference call sites; samples.go:188-193)."""
    unit = {1e-9: "ns", 1e-6: "us", 1e-3: "ms", 1.0: "s"}.get(resolution, "")
    return histogram(name, seconds / resolution, tags, unit=unit, **kw)


def status(name: str, state,
           tags: Optional[Dict[str, str]] = None, **kw) -> sample_pb2.SSFSample:
    return _create(sample_pb2.SSFSample.STATUS, name, 0.0, tags,
                   status=state, **kw)


def randomly_sample(rate: float,
                    *samples: sample_pb2.SSFSample) -> List[sample_pb2.SSFSample]:
    """Thin a batch to ~rate, scaling survivors' sample_rate
    (samples.go:112-134)."""
    out = []
    for s in samples:
        if random.random() <= rate:
            if 0 < rate <= 1:
                s.sample_rate = s.sample_rate * rate
            out.append(s)
    return out


def now_timestamp() -> int:
    return int(time_mod.time())
